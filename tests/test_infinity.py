"""ZeRO-Infinity training tier (reference
`runtime/swap_tensor/partitioned_param_swapper.py:36` + `zero/stage3.py`
NVMe integration): streamed-layer training with host-resident fp32 state."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                      make_gpt_layered_model, gpt_loss)
from deepspeed_tpu.runtime.infinity import InfinityEngine

DEEP = GPTConfig(n_layer=6, n_head=4, d_model=64, d_ff=128, max_seq_len=64,
                 vocab_size=128, dtype=jnp.float32, remat=False)


def _batches(n, B=4, T=17, seed=0):
    rng = np.random.default_rng(seed)
    return [{"tokens": rng.integers(0, DEEP.vocab_size, (B, T)).astype(np.int32)}
            for _ in range(n)]


@pytest.mark.parametrize("offload_device", ["cpu", "nvme"])
def test_infinity_trains_and_bounds_hbm(offload_device, tmp_path):
    """Loss decreases over steps while device memory never holds more than
    lookahead+1 layers of weights — training a model the device could not
    hold is the whole capability."""
    params = init_gpt_params(DEEP, seed=0)
    spec = make_gpt_layered_model(cfg=DEEP, name="inf", params=params)
    kw = {"offload_device": offload_device}
    if offload_device == "nvme":
        kw["nvme_path"] = str(tmp_path / "w")
        kw["optimizer_nvme_path"] = str(tmp_path / "opt")
    eng = InfinityEngine(spec, lr=1e-2, dtype=jnp.float32, **kw)
    batch = _batches(1)[0]
    losses = [eng.train_batch(batch) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    assert eng.streamer.peak_live_layers <= 2
    assert eng.peak_param_hbm_bytes * 3 <= eng.store.layer_bytes * eng.L
    eng.release()


def test_infinity_matches_dense_adamw_trajectory():
    """The streamed layer-at-a-time backward + per-layer host Adam must walk
    the SAME trajectory as an ordinary whole-model Adam on the same loss
    (fp32 everywhere, same init): losses match step-for-step to fp32
    tolerance. This pins the per-layer vjp composition (boundary activations,
    tied-embedding grad accumulation across head+embed) and the C++ Adam
    against optax."""
    import optax
    params = init_gpt_params(DEEP, seed=1)
    spec = make_gpt_layered_model(cfg=DEEP, name="inf", params=params)
    eng = InfinityEngine(spec, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                         weight_decay=0.0, dtype=jnp.float32,
                         offload_device="cpu")

    opt = optax.adam(1e-3, b1=0.9, b2=0.999, eps=1e-8)
    ref_params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32),
                                        params)
    opt_state = opt.init(ref_params)

    @jax.jit
    def ref_step(p, s, tokens):
        loss, g = jax.value_and_grad(
            lambda p_: gpt_loss(p_, {"tokens": tokens}, None, cfg=DEEP))(p)
        upd, s = opt.update(g, s, p)
        return optax.apply_updates(p, upd), s, loss

    for step, b in enumerate(_batches(5, seed=3)):
        loss_inf = eng.train_batch(b)
        ref_params, opt_state, loss_ref = ref_step(ref_params, opt_state,
                                                   jnp.asarray(b["tokens"]))
        np.testing.assert_allclose(loss_inf, float(loss_ref), rtol=2e-4,
                                   atol=2e-4, err_msg=f"step {step}")
    eng.release()


def test_initialize_routes_layered_spec_to_infinity(tmp_path):
    """Reference config surface: deepspeed.initialize with stage-3 param
    offload reaches the swap tier — here a LayeredModelSpec + offload_param
    device routes to InfinityEngine through the same initialize() call."""
    import deepspeed_tpu
    params = init_gpt_params(DEEP, seed=2)
    spec = make_gpt_layered_model(cfg=DEEP, name="inf", params=params)
    eng, _, _, _ = deepspeed_tpu.initialize(model=spec, config={
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": "nvme",
                              "nvme_path": str(tmp_path / "w")},
            "offload_optimizer": {"device": "nvme",
                                  "nvme_path": str(tmp_path / "o")}}})
    assert isinstance(eng, InfinityEngine)
    batch = _batches(1, seed=9)[0]
    losses = [eng.train_batch(batch) for _ in range(5)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    eng.release()

    # refusal: layered spec without an offload device is a config error
    with pytest.raises(AssertionError, match="offload_param"):
        deepspeed_tpu.initialize(model=spec, config={
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})


def test_infinity_fp16_dynamic_loss_scaling():
    """fp16 through the Infinity tier (VERDICT r4 item 6; reference stage-3 +
    offload supports dynamic loss scaling, `zero/stage3.py:1999`): training
    converges, the scale grows after the window, and an overflow (forced via
    an fp16-range-exceeding scale) skips the step and halves the scale
    without touching weights."""
    import deepspeed_tpu
    params = init_gpt_params(DEEP, seed=3)
    spec = make_gpt_layered_model(cfg=DEEP, name="inf-fp16", params=params)
    eng, _, _, _ = deepspeed_tpu.initialize(model=spec, config={
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
        "fp16": {"enabled": True, "initial_scale_power": 8,
                 "loss_scale_window": 2, "hysteresis": 1},
        "zero_optimization": {"stage": 3,
                              "offload_param": {"device": "cpu"}}})
    assert isinstance(eng, InfinityEngine)
    assert eng.fp16 and eng.cur_scale == 256.0
    assert eng.dtype == jnp.float16
    batch = _batches(1, seed=4)[0]
    losses = [eng.train_batch(batch) for _ in range(6)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses
    assert eng.cur_scale > 256.0, "dynamic scale never grew (window=2)"

    # force an overflow: a scale beyond fp16 range makes the scaled grads inf
    store_before = [a.copy() for a in eng.store.get(0)]
    steps_before = eng.step_count
    eng.cur_scale = 2.0 ** 40
    eng.train_batch(batch)
    assert eng.skipped_steps >= 1, "overflow did not skip the step"
    assert eng.cur_scale == 2.0 ** 39, "overflow did not halve the scale"
    assert eng.step_count == steps_before, "skipped step must not count"
    for a, b in zip(store_before, eng.store.get(0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg="weights changed on a skipped step")
    # recovery: training continues at the halved scale chain
    l2 = [eng.train_batch(batch) for _ in range(2)]
    assert np.isfinite(l2).all()
    eng.release()


def test_infinity_gradient_accumulation_matches_big_batch():
    """gas=2 over two micro-batches must walk the same trajectory as gas=1
    on the concatenated batch (mean-loss semantics make the mean of
    micro-grads equal the big-batch grad)."""
    params = init_gpt_params(DEEP, seed=4)
    spec = make_gpt_layered_model(cfg=DEEP, name="inf", params=params)
    big = _batches(3, B=8, seed=11)

    e_gas = InfinityEngine(spec, lr=1e-2, dtype=jnp.float32,
                           offload_device="cpu",
                           gradient_accumulation_steps=2)
    e_ref = InfinityEngine(spec, lr=1e-2, dtype=jnp.float32,
                           offload_device="cpu")
    for step, b in enumerate(big):
        l1 = e_gas.train_batch(b)    # split internally into 2 micro-batches
        l2 = e_ref.train_batch(b)    # one big batch
        np.testing.assert_allclose(l1, l2, rtol=2e-4, atol=2e-4,
                                   err_msg=f"step {step}")
    e_gas.release()
    e_ref.release()


def test_infinity_gradient_clipping_matches_optax():
    """Clipping parity (the reference stage-3 + offload clips a global norm):
    Infinity with gradient_clipping must walk the same trajectory as
    optax clip_by_global_norm -> adam on the same loss. A tiny clip value
    guarantees the scale actually engages every step."""
    import optax
    params = init_gpt_params(DEEP, seed=5)
    spec = make_gpt_layered_model(cfg=DEEP, name="inf", params=params)
    CLIP = 0.05
    eng = InfinityEngine(spec, lr=1e-2, betas=(0.9, 0.999), eps=1e-8,
                         weight_decay=0.0, dtype=jnp.float32,
                         offload_device="cpu", gradient_clipping=CLIP)

    opt = optax.chain(optax.clip_by_global_norm(CLIP),
                      optax.adam(1e-2, b1=0.9, b2=0.999, eps=1e-8))
    ref_params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32),
                                        params)
    opt_state = opt.init(ref_params)

    @jax.jit
    def ref_step(p, s, tokens):
        loss, g = jax.value_and_grad(
            lambda p_: gpt_loss(p_, {"tokens": tokens}, None, cfg=DEEP))(p)
        upd, s = opt.update(g, s, p)
        return optax.apply_updates(p, upd), s, loss

    for step, b in enumerate(_batches(5, seed=7)):
        loss_inf = eng.train_batch(b)
        ref_params, opt_state, loss_ref = ref_step(ref_params, opt_state,
                                                   jnp.asarray(b["tokens"]))
        np.testing.assert_allclose(loss_inf, float(loss_ref), rtol=3e-4,
                                   atol=3e-4, err_msg=f"step {step}")
        assert eng.last_grad_norm is not None and eng.last_grad_norm > CLIP
    eng.release()


def test_infinity_dataloader_and_initialize_clip(tmp_path):
    """training_data through initialize() builds the tier's dataloader and
    gradient_clipping routes through the config (both were refused loudly in
    r3 — now parity with reference stage-3 + offload)."""
    import deepspeed_tpu
    params = init_gpt_params(DEEP, seed=6)
    spec = make_gpt_layered_model(cfg=DEEP, name="inf", params=params)
    data = [{"tokens": row} for row in
            np.random.default_rng(0).integers(
                0, DEEP.vocab_size, (32, 17)).astype(np.int32)]
    eng, _, loader, _ = deepspeed_tpu.initialize(
        model=spec, training_data=data, config={
            "train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 2,
            "gradient_clipping": 1.0,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3,
                                  "offload_param": {"device": "cpu"}}})
    assert isinstance(eng, InfinityEngine)
    assert loader is not None and eng.gradient_clipping == 1.0
    losses = [eng.train_batch() for _ in range(4)]   # no batch: loader feeds
    assert np.isfinite(losses).all()
    assert eng.last_grad_norm is not None
    eng.release()
