"""CIFAR-10 training smoke — the reference's getting-started tutorial
(`docs/_tutorials/cifar-10.md`, BASELINE.md ladder rung 1), TPU-native.

A small NHWC CNN (channels-last is the TPU-native conv layout) trained through
`deepspeed_tpu.initialize`/`train_batch`. Uses the real CIFAR-10 if a numpy
copy is available locally (--data /path/with/cifar10.npz), otherwise a
synthetic stand-in of the same shape/cardinality so the smoke runs in
zero-egress environments.

    python examples/cifar10.py --steps 20
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/cifar10.py --cpu --steps 4 --zero 2
"""

import argparse
import importlib.util
import os
import sys

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def init_cnn_params(rng, dtype):
    import jax.numpy as jnp

    def conv(cin, cout):  # 3x3 HWIO
        fan_in = 9 * cin
        return jnp.asarray(rng.normal(0, (2.0 / fan_in) ** 0.5, (3, 3, cin, cout)),
                           dtype)

    return {
        "c1": conv(3, 32), "c2": conv(32, 64), "c3": conv(64, 128),
        "w": jnp.asarray(rng.normal(0, 0.05, (128, 10)), dtype),
        "b": jnp.zeros((10,), dtype),
    }


def cnn_loss(params, batch):
    import jax
    import jax.numpy as jnp

    x = batch["image"]                       # [B, 32, 32, 3] NHWC
    dn = jax.lax.conv_dimension_numbers(x.shape, params["c1"].shape,
                                        ("NHWC", "HWIO", "NHWC"))

    def block(x, w):                         # conv → relu → 2x2 avg-pool
        x = jax.lax.conv_general_dilated(x, w.astype(x.dtype), (1, 1), "SAME",
                                         dimension_numbers=dn)
        x = jax.nn.relu(x)
        return jax.lax.reduce_window(x, 0.0, jax.lax.add, (1, 2, 2, 1),
                                     (1, 2, 2, 1), "VALID") / 4.0

    x = block(x, params["c1"])               # 16x16x32
    x = block(x, params["c2"])               # 8x8x64
    x = block(x, params["c3"])               # 4x4x128
    x = jnp.mean(x, axis=(1, 2))             # global average pool → [B, 128]
    logits = (x @ params["w"] + params["b"]).astype(jnp.float32)
    labels = batch["label"]
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return (logz - gold).mean()


def load_data(path, n):
    import numpy as np

    if path and os.path.exists(path):
        d = np.load(path)
        return (d["x_train"][:n].astype(np.float32) / 127.5 - 1.0,
                d["y_train"][:n].astype(np.int32).reshape(-1))
    print("[cifar10] no local dataset — using synthetic CIFAR-shaped data "
          "(class-dependent means, so loss visibly drops)")
    rng = np.random.default_rng(0)
    y = rng.integers(0, 10, (n,)).astype(np.int32)
    means = rng.normal(0, 1.0, (10, 1, 1, 3)).astype(np.float32)
    x = rng.normal(0, 0.5, (n, 32, 32, 3)).astype(np.float32) + means[y]
    return x, y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--cpu", action="store_true", help="8 virtual CPU devices")
    p.add_argument("--data", default=None, help="path to cifar10.npz")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--zero", type=int, default=1)
    args = p.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import jax.numpy as jnp
    import deepspeed_tpu

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=cnn_loss,
        model_parameters=init_cnn_params(np.random.default_rng(0), jnp.float32),
        config={
            "train_micro_batch_size_per_gpu": args.batch,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": args.zero},
            "steps_per_print": 5,
        })

    gb = engine.train_batch_size()
    x, y = load_data(args.data, n=max(2048, gb))
    rng = np.random.default_rng(1)
    first = last = None
    for step in range(args.steps):
        idx = rng.integers(0, len(x), (gb,))
        loss = float(engine.train_batch({"image": x[idx], "label": y[idx]}))
        first = first if first is not None else loss
        last = loss
    print(f"loss {first:.4f} -> {last:.4f} over {args.steps} steps "
          f"(global batch {gb})")
    assert np.isfinite(last)


if __name__ == "__main__":
    main()
