"""Generate text from a HuggingFace checkpoint via the inference engine.

    python examples/generate_hf.py --model gpt2 --prompt "The TPU is" \
        --max_new_tokens 32 [--tp 4] [--int8]

Covers: HF weight adaptation (no module surgery — the adapter emits a jitted
decode model), tensor-parallel sharding, weight-only quantization.
"""

import argparse
import importlib.util
import os
import sys

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2",
                   help="HF model id (gpt2 / llama / opt / bloom / neox / gptj "
                        "/ mistral families)")
    p.add_argument("--prompt", default="Hello")
    p.add_argument("--max_new_tokens", type=int, default=32)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--int8", action="store_true", help="weight-only int8")
    p.add_argument("--greedy", action="store_true")
    args = p.parse_args()

    import numpy as np
    from transformers import AutoModelForCausalLM, AutoTokenizer
    import deepspeed_tpu
    from deepspeed_tpu.inference.adapters import hf_decode_model

    try:
        tok = AutoTokenizer.from_pretrained(args.model)
        hf_model = AutoModelForCausalLM.from_pretrained(args.model)
    except OSError as e:
        # zero-egress / uncached environment: demonstrate the identical
        # adapter path on a randomly-initialized HF config instead
        print(f"[generate_hf] '{args.model}' not downloadable/cached ({e});\n"
              "falling back to a RANDOM-weight tiny GPT-2 config — the "
              "adapter/engine path is identical, the text is gibberish.")
        from transformers import AutoConfig
        cfg = AutoConfig.for_model("gpt2", n_layer=2, n_head=4, n_embd=128,
                                   n_positions=256)
        hf_model = AutoModelForCausalLM.from_config(cfg)
        tok = None
    spec = hf_decode_model(hf_model)

    engine = deepspeed_tpu.init_inference(
        model=spec,
        config={"dtype": "bfloat16",
                "tensor_parallel": {"tp_size": args.tp},
                "quant": {"enabled": args.int8, "bits": 8},
                "greedy": args.greedy})

    if tok is not None:
        ids = np.asarray(tok(args.prompt)["input_ids"], np.int32)[None, :]
    else:
        ids = np.asarray([[1, 2, 3, 4]], np.int32)
    out = engine.generate(ids, max_new_tokens=args.max_new_tokens)
    full = np.concatenate([ids[0], np.asarray(out[0])])
    print(tok.decode(full) if tok is not None else f"token ids: {full.tolist()}")


if __name__ == "__main__":
    main()
