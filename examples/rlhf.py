"""RLHF end-to-end on the Hybrid Engine — the DS-Chat actor loop in miniature.

Reference: `runtime/hybrid_engine.py:32` exists to serve DeepSpeed-Chat
(`README.md:16`): inside one step the actor model GENERATES rollouts with
inference-grade speed and TRAINS on them with ZeRO partitioning. Here the
same loop runs TPU-native: `HybridEngine.generate()` samples rollouts from
the CURRENT training params (no gather/release juggling — sharded params are
logically whole), a reward scores them, and a REINFORCE-style policy-gradient
`train_batch` updates the very same params.

Toy objective: reward = fraction of rollout tokens equal to TARGET_TOKEN.
With a random init that starts near 1/vocab; ~20 policy-gradient steps push
it up by an order of magnitude, closing the generate -> reward -> train loop
the reference's flagship claims are built on.

Run:  python examples/rlhf.py        (CPU mesh or a real chip)
"""

import dataclasses
import importlib.util
import os
import sys

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.gpt import (GPTConfig, gpt_forward, init_gpt_params,
                                      make_gpt_decode_model)
from deepspeed_tpu.runtime.engine import ModelSpec
from deepspeed_tpu.runtime.hybrid_engine import HybridEngine
from deepspeed_tpu.config.core import TpuTrainConfig

TARGET_TOKEN = 7


def build_actor(cfg: GPTConfig, ds_config, seed=0):
    """HybridEngine whose training loss is REINFORCE on rollout tokens."""
    params = init_gpt_params(cfg, seed=seed)

    def pg_loss(p, batch, rng=None):
        tokens = batch["tokens"]            # [B, T] prompt + rollout
        mask = batch["rollout_mask"]        # [B, T] 1.0 on rollout positions
        adv = batch["advantage"]            # [B] centered reward
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits = gpt_forward(p, inputs, cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tok_logp = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        m = mask[:, 1:]
        seq_logp = jnp.sum(tok_logp * m, axis=1) / jnp.maximum(jnp.sum(m, 1), 1.0)
        return -jnp.mean(seq_logp * adv)

    engine = HybridEngine(ModelSpec(loss_fn=pg_loss, params=params,
                                    name="rlhf-actor"),
                          TpuTrainConfig.load(ds_config))
    engine.set_decode_spec(make_gpt_decode_model(cfg=cfg, name="rlhf-actor",
                                                 params=params))
    return engine


def reward_fn(rollouts):
    """[B, N] tokens -> [B] fraction equal to TARGET_TOKEN."""
    return (np.asarray(rollouts) == TARGET_TOKEN).mean(axis=1)


def rlhf_loop(steps=20, batch=16, prompt_len=8, max_new=8, seed=0,
              top_k=0, verbose=True):
    """generate -> reward -> policy-gradient train, on one set of params.
    Returns the per-step mean rewards."""
    cfg = GPTConfig(n_layer=2, n_head=4, d_model=64, d_ff=128, max_seq_len=64,
                    vocab_size=64, dtype=jnp.float32, remat=False)
    engine = build_actor(cfg, {
        "train_micro_batch_size_per_gpu": batch,
        "optimizer": {"type": "Adam", "params": {"lr": 5e-2}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10 ** 9,
    }, seed=seed)

    rng = np.random.default_rng(seed)
    rewards = []
    for step in range(steps):
        prompts = rng.integers(0, cfg.vocab_size, (batch, prompt_len)).astype(np.int32)
        # 1) rollout from the CURRENT training params
        rollouts = engine.generate(prompts, max_new_tokens=max_new,
                                   greedy=False, temperature=1.0, top_k=top_k)
        # 2) reward + centered advantage (REINFORCE baseline = batch mean)
        r = reward_fn(rollouts)
        adv = (r - r.mean()) / (r.std() + 1e-6)
        # 3) train on the same params the rollout came from
        tokens = np.concatenate([prompts, rollouts], axis=1)
        mask = np.concatenate([np.zeros_like(prompts, np.float32),
                               np.ones_like(rollouts, np.float32)], axis=1)
        engine.train_batch({"tokens": tokens, "rollout_mask": mask,
                            "advantage": adv.astype(np.float32)})
        rewards.append(float(r.mean()))
        if verbose:
            print(f"step {step:3d}  reward {r.mean():.4f}")
    return rewards


if __name__ == "__main__":
    rewards = rlhf_loop()
    first, last = np.mean(rewards[:3]), np.mean(rewards[-3:])
    print(f"mean reward: first3 {first:.4f} -> last3 {last:.4f}")
    assert last > first, "reward did not improve"
