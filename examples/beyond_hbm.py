"""Train AND serve a model whose parameters exceed device memory.

The ZeRO-Infinity / ZeRO-Inference walkthrough (reference capabilities:
`docs/_posts/2022-09-10-zero-inference.md` "15T-param inference on one GPU",
`runtime/swap_tensor/partitioned_param_swapper.py` training-side swap):
weights live on host RAM or NVMe and stream through HBM layer by layer, so
model size is bounded by disk, not device memory.

  python examples/beyond_hbm.py            # host-RAM tier
  python examples/beyond_hbm.py --nvme /path/to/scratch

Swap the tiny config for a real one and the same code trains/serves models
many times larger than the chip's HBM: the device working set is the
resident leaves + 2 layers + activations, independent of depth.
"""

import argparse
import importlib.util
import os
import sys

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.gpt import (GPTConfig, init_gpt_params,
                                      make_gpt_layered_model)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nvme", default=None,
                    help="scratch dir for the NVMe tier (default: host RAM)")
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    cfg = GPTConfig(n_layer=8, n_head=8, d_model=256, d_ff=1024,
                    max_seq_len=128, vocab_size=512, dtype=jnp.bfloat16,
                    remat=False)
    params = init_gpt_params(cfg, seed=0)
    spec = make_gpt_layered_model(cfg=cfg, name="beyond-hbm", params=params)

    device = "nvme" if args.nvme else "cpu"
    nvme = args.nvme or ""  # unused on the host-RAM tier

    # ---- training: the reference's stage-3 + offload_param config surface
    engine, _, _, _ = deepspeed_tpu.initialize(model=spec, config={
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
        "zero_optimization": {
            "stage": 3,
            "offload_param": {"device": device,
                              "nvme_path": nvme + "/w" if args.nvme else None},
            "offload_optimizer": {"device": device,
                                  "nvme_path": nvme + "/o" if args.nvme else None},
        }})
    rng = np.random.default_rng(0)
    batch = {"tokens": rng.integers(0, cfg.vocab_size, (8, 65)).astype(np.int32)}
    for step in range(args.steps):
        loss = engine.train_batch(batch)
        print(f"step {step:2d}  loss {loss:.4f}  "
              f"(HBM holds {engine.streamer.peak_live_layers} of "
              f"{engine.L} layers)")
    engine.release()

    # ---- inference: same weights, streamed decode
    infer = deepspeed_tpu.init_inference(
        model=make_gpt_layered_model(cfg=cfg, name="beyond-hbm", params=params),
        config={"dtype": "bfloat16", "greedy": True,
                "zero": {"offload_param": {
                    "device": device,
                    "nvme_path": nvme + "/iw" if args.nvme else None}}})
    prompts = rng.integers(0, cfg.vocab_size, (4, 12)).astype(np.int32)
    out = infer.generate(prompts, max_new_tokens=16)
    print("generated:", out.shape, "— total params",
          f"{infer.total_param_bytes / 1e6:.1f} MB,",
          f"peak resident {infer.peak_param_hbm_bytes / 1e6:.1f} MB")

    # ---- streamed serving: the continuous-batching scheduler over the
    # same spilled weights (paged pool resident, weights staged per layer)
    from deepspeed_tpu.inference.scheduler import Request
    serving = infer.serving(max_slots=4, max_context=128, prefill_chunk=32)
    reqs = [Request(uid=i,
                    tokens=rng.integers(0, cfg.vocab_size,
                                        (int(n),)).astype(np.int32),
                    max_new_tokens=8, stop_on_eos=False)
            for i, n in enumerate([9, 21, 14, 30])]
    done = serving.run(reqs)
    stg = serving.stats()["offload"]["staging"]
    print(f"served {len(done)} requests streamed — staging hit rate "
          f"{stg['hit_rate']:.0%}, stall {stg['stall_ms_total']:.1f} ms, "
          f"compiles {serving.compile_stats()}")
    infer.release()


if __name__ == "__main__":
    main()
