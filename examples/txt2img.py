"""Text-to-image with the diffusion family: one compiled DDIM denoise loop.

Run (random toy weights; swap in adapted SD weights for real output):
    python examples/txt2img.py --steps 10 --latent 16

CPU smoke test:
    JAX_PLATFORMS=cpu python examples/txt2img.py --steps 2 --latent 8
"""

import argparse
import importlib.util
import os
import sys
import time

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--latent", type=int, default=16, help="latent H=W")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--guidance", type=float, default=7.5)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models.diffusion import (
        UNetConfig, VAEDecoderConfig, init_unet_params,
        init_vae_decoder_params, clip_text_config, make_txt2img)
    from deepspeed_tpu.models.gpt import init_gpt_params

    ucfg = UNetConfig(block_channels=(64, 128), attn_levels=(1,), heads=4,
                      context_dim=128, groups=16)
    vcfg = VAEDecoderConfig(block_channels=(64, 32), layers_per_block=1)
    tcfg = clip_text_config(vocab_size=1000, width=128, layers=2, heads=4)

    pipe = make_txt2img(init_unet_params(ucfg), ucfg,
                        init_vae_decoder_params(vcfg), vcfg,
                        init_gpt_params(tcfg), tcfg,
                        steps=args.steps, guidance_scale=args.guidance,
                        latent_hw=args.latent)

    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(1, 1000, (args.batch, 16)), jnp.int32)
    uncond = jnp.zeros((args.batch, 16), jnp.int32)

    t0 = time.perf_counter()
    img = pipe(prompt, uncond, jax.random.PRNGKey(0))
    img.block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    img = pipe(prompt, uncond, jax.random.PRNGKey(1))
    float(jnp.sum(img))
    run_s = time.perf_counter() - t0
    print(f"images {tuple(img.shape)} range [{float(img.min()):.3f}, "
          f"{float(img.max()):.3f}] | compile {compile_s:.1f}s | "
          f"denoise+decode {run_s*1e3:.0f} ms for {args.steps} steps")


if __name__ == "__main__":
    main()
