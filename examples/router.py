"""Distributed serving demo: a 2-replica ServingRouter with prefix-affinity
routing on a shared-system-prompt workload, then a replica failure mid-trace
(docs/inference.md "Distributed serving").

Run on any backend (CPU works):
    python examples/router.py
"""

import importlib.util
import os
import sys

import numpy as np

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu
from deepspeed_tpu.inference.scheduler import Request
from deepspeed_tpu.models.gpt import GPT2_CONFIGS, make_gpt_decode_model
from deepspeed_tpu.serving import ServingRouter


def make_engine():
    return deepspeed_tpu.init_inference(
        model=make_gpt_decode_model(name="gpt2-tiny"),
        config={"dtype": "bfloat16", "kv_cache_dtype": "bfloat16",
                "greedy": True, "kv_block_size": 64, "max_out_tokens": 256,
                "serving": {"max_slots": 4, "prefill_chunk": 64,
                            "enable_prefix_caching": True}})


def shared_prefix_requests(n, uid_base=0):
    """Chat-style traffic: every request opens with the same 128-token
    system prompt (2 full 64-token blocks — the affinity key)."""
    vocab = GPT2_CONFIGS["gpt2-tiny"].vocab_size
    rng = np.random.default_rng(0)
    system_prompt = rng.integers(0, vocab, 128)
    out = []
    for i in range(n):
        user_turn = rng.integers(0, vocab, int(rng.integers(5, 40)))
        out.append(Request(uid=uid_base + i,
                           tokens=np.concatenate([system_prompt, user_turn]),
                           max_new_tokens=16))
    return out


def affinity_demo(engine):
    """Affinity routing sends the whole shared-prefix wave to ONE replica:
    the system prompt prefills once per POOL, not once per replica."""
    router = ServingRouter(replicas=[engine.serving(), engine.serving()])
    res = router.run(shared_prefix_requests(8))
    c = router.counters
    print(f"completed {len(res)} requests over {len(router.replicas)} "
          f"replicas")
    print(f"affinity hit-rate: {c['affinity_hits'] / c['submitted']:.0%} "
          f"({c['affinity_hits']}/{c['submitted']} dispatches landed on a "
          f"replica already holding the prompt's prefix)")
    for rid, rep in router.replicas.items():
        st = rep.stats()
        print(f"  {rid}: prefill_chunks={st['prefill_chunks']} "
              f"tokens={st['tokens_generated']} "
              f"compiles={rep.compile_stats()}")
    print(f"total prefill chunks: {router.total_prefill_chunks()} "
          f"(round-robin would pay the shared prefix once per replica)")


def failover_demo(engine):
    """Kill a replica mid-trace: its queued AND in-flight requests re-route
    to the survivor and the whole trace completes exactly once each."""
    router = ServingRouter(replicas=[engine.serving(), engine.serving()])
    for r in shared_prefix_requests(8, uid_base=100):
        router.submit(r)
    done = {}
    for _ in range(3):                       # let work spread
        for d in router.step():
            done[d.uid] = d
    victim = next(rec.replica for rec in router._pending.values()
                  if rec.replica is not None)
    print(f"killing replica {victim} with {router.in_flight} requests live")
    router.kill_replica(victim)
    while router.in_flight:
        for d in router.step():
            done[d.uid] = d
    c = router.counters
    print(f"trace completed: {len(done)}/8 requests "
          f"(reroutes={c['reroutes']}, failures={c['replica_failures']}); "
          f"replica {victim} is "
          f"{router.stats()['replicas'][victim]['health']}")


if __name__ == "__main__":
    engine = make_engine()
    print("== prefix-affinity routing ==")
    affinity_demo(engine)
    print("\n== replica failover ==")
    failover_demo(engine)
