"""Continuous-batching serving demo: a ragged stream of requests through the
paged KV-cache pool + scheduler (docs/inference.md "Continuous-batching
serving").

Run on any backend (CPU works):
    python examples/serving.py

Swap the toy model for an HF checkpoint with
`inference.adapters.hf_decode_model` — the serving layer only needs the
paged contract the GPT zoo provides.
"""

import importlib.util
import os
import sys

import numpy as np

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu
from deepspeed_tpu.inference.scheduler import Request
from deepspeed_tpu.models.gpt import GPT2_CONFIGS, make_gpt_decode_model


def main():
    engine = deepspeed_tpu.init_inference(
        model=make_gpt_decode_model(name="gpt2-tiny"),
        config={"dtype": "bfloat16", "kv_cache_dtype": "bfloat16",
                "greedy": True, "kv_block_size": 64, "max_out_tokens": 256,
                "serving": {"max_slots": 4, "prefill_chunk": 64,
                            "decode_steps_per_sync": 4}})
    serving = engine.serving()

    vocab = GPT2_CONFIGS["gpt2-tiny"].vocab_size
    rng = np.random.default_rng(0)
    for i, (plen, nnew) in enumerate([(17, 24), (90, 8), (5, 40), (33, 16),
                                      (140, 12), (9, 32)]):
        serving.submit(Request(uid=f"req{i}",
                               tokens=rng.integers(0, vocab, plen),
                               max_new_tokens=nnew))

    while serving.queue or serving.num_active:
        for done in serving.step():
            print(f"{done.uid}: prompt {done.prompt_len} tokens -> "
                  f"{len(done.tokens)} generated ({done.finish_reason}); "
                  f"free blocks now {serving.allocator.num_free}")
    print("scheduler:", serving.stats())


if __name__ == "__main__":
    main()
