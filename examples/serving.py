"""Continuous-batching serving demo: a ragged stream of requests through the
paged KV-cache pool + scheduler, then a shared-system-prompt workload with
automatic prefix caching (docs/inference.md "Continuous-batching serving" /
"Automatic prefix caching").

Run on any backend (CPU works):
    python examples/serving.py

Swap the toy model for an HF checkpoint with
`inference.adapters.hf_decode_model` — the serving layer only needs the
paged contract the GPT zoo provides.
"""

import importlib.util
import os
import sys

import numpy as np

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import deepspeed_tpu
from deepspeed_tpu.inference.scheduler import Request
from deepspeed_tpu.models.gpt import GPT2_CONFIGS, make_gpt_decode_model


def make_engine():
    return deepspeed_tpu.init_inference(
        model=make_gpt_decode_model(name="gpt2-tiny"),
        config={"dtype": "bfloat16", "kv_cache_dtype": "bfloat16",
                "greedy": True, "kv_block_size": 64, "max_out_tokens": 256,
                "serving": {"max_slots": 4, "prefill_chunk": 64,
                            "decode_steps_per_sync": 4}})


def ragged_demo(engine):
    """Mixed prompt/output lengths through the continuous-batching core."""
    serving = engine.serving()
    vocab = GPT2_CONFIGS["gpt2-tiny"].vocab_size
    rng = np.random.default_rng(0)
    for i, (plen, nnew) in enumerate([(17, 24), (90, 8), (5, 40), (33, 16),
                                      (140, 12), (9, 32)]):
        serving.submit(Request(uid=f"req{i}",
                               tokens=rng.integers(0, vocab, plen),
                               max_new_tokens=nnew))

    while serving.queue or serving.num_active:
        for done in serving.step():
            print(f"{done.uid}: prompt {done.prompt_len} tokens -> "
                  f"{len(done.tokens)} generated ({done.finish_reason}); "
                  f"free blocks now {serving.allocator.num_free}")
    print("scheduler:", serving.stats())


def prefix_caching_demo(engine):
    """A chat-style workload: every request begins with the same 128-token
    system prompt. With enable_prefix_caching the prompt prefills ONCE —
    every later request maps the cached KV blocks and skips those chunks."""
    serving = engine.serving(enable_prefix_caching=True)
    vocab = GPT2_CONFIGS["gpt2-tiny"].vocab_size
    rng = np.random.default_rng(1)
    system_prompt = rng.integers(0, vocab, 128)           # 2 full 64-blocks
    for i in range(8):
        user_turn = rng.integers(0, vocab, int(rng.integers(5, 40)))
        serving.submit(Request(uid=f"chat{i}",
                               tokens=np.concatenate([system_prompt,
                                                      user_turn]),
                               max_new_tokens=16))

    prompt_tokens = cached_tokens = 0
    while serving.queue or serving.num_active:
        for done in serving.step():
            prompt_tokens += done.prompt_len
            cached_tokens += done.cached_prefix_tokens
            print(f"{done.uid}: prompt {done.prompt_len} tokens, "
                  f"{done.cached_prefix_tokens} served from the prefix cache")
    st = serving.stats()["prefix_cache"]
    print(f"prefix cache: {cached_tokens}/{prompt_tokens} prompt tokens "
          f"({100 * cached_tokens / prompt_tokens:.0f}%) from cache, "
          f"{st['prefill_chunks_skipped']} prefill chunks skipped, "
          f"{st['evictions']} evictions, "
          f"{st['cached_blocks']} blocks registered")
    print("compiles (still one per program):", serving.compile_stats())


def main():
    engine = make_engine()
    ragged_demo(engine)
    print()
    prefix_caching_demo(engine)


if __name__ == "__main__":
    main()
