"""Pretrain a GPT-2 family model with ZeRO + mixed precision.

Run single-host (drives all local chips):
    python examples/train_gpt2.py --model gpt2-125m --steps 50

Multi-host via the launcher:
    dstpu --hostfile /job/hostfile examples/train_gpt2.py --model gpt2-1.3b

CPU smoke test (8 virtual devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_gpt2.py --cpu --model gpt2-tiny --steps 4 --zero 3
"""

import argparse
import importlib.util
import os
import sys

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="gpt2-125m")
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--micro_batch", type=int, default=8)
    p.add_argument("--gas", type=int, default=1)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--zero", type=int, default=1)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--data", type=int, default=-1, help="data-parallel axis size")
    p.add_argument("--tensor", type=int, default=1)
    p.add_argument("--sequence", type=int, default=1)
    p.add_argument("--ckpt_dir", default=None)
    p.add_argument("--cpu", action="store_true", help="force CPU backend (smoke test)")
    args = p.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt import GPT2_CONFIGS, make_gpt_model
    import dataclasses

    cfg = dataclasses.replace(GPT2_CONFIGS[args.model], dtype=jnp.bfloat16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=make_gpt_model(cfg=cfg, name=args.model),
        config={
            "train_micro_batch_size_per_gpu": args.micro_batch,
            "gradient_accumulation_steps": args.gas,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": args.lr, "weight_decay": 0.1}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_num_steps": max(args.steps // 10, 1)}},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "zero_optimization": {"stage": args.zero},
            "mesh": {"data": args.data, "tensor": args.tensor,
                     "sequence": args.sequence},
            "steps_per_print": 10,
        })

    # synthetic data — swap in engine.deepspeed_io(dataset) for a real corpus
    rng = np.random.default_rng(0)
    seq = min(args.seq, cfg.max_seq_len)
    for step in range(args.steps):
        batch = {"tokens": rng.integers(
            0, cfg.vocab_size, (engine.train_batch_size(), seq + 1)).astype(np.int32)}
        loss = engine.train_batch(batch)
        if step % 10 == 0:
            print(f"step {step}: loss {float(loss):.4f}")
    print(f"final loss: {float(loss):.4f}")

    if args.ckpt_dir:
        engine.save_checkpoint(args.ckpt_dir)


if __name__ == "__main__":
    main()
