"""Train a Mixture-of-Experts GPT with expert parallelism.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/train_moe.py --cpu --experts 4 --ep 4 --steps 4
"""

import argparse
import importlib.util
import os
import sys

if importlib.util.find_spec("deepspeed_tpu") is None:  # running from a checkout
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--ep", type=int, default=1, help="expert-parallel axis size")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--micro_batch", type=int, default=4)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import deepspeed_tpu
    from deepspeed_tpu.models.moe_gpt import MoEGPTConfig, make_moe_gpt_model

    cfg = MoEGPTConfig(n_layer=4, n_head=8, d_model=256, d_ff=1024,
                       max_seq_len=256, vocab_size=8192, dtype=jnp.bfloat16,
                       num_experts=args.experts, moe_freq=2)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=make_moe_gpt_model(cfg),
        config={
            "train_micro_batch_size_per_gpu": args.micro_batch,
            "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": -1, "expert": args.ep},
            "steps_per_print": 5,
        })

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"tokens": rng.integers(
            0, cfg.vocab_size, (engine.train_batch_size(), 129)).astype(np.int32)}
        loss = engine.train_batch(batch)
        if step % 5 == 0:
            print(f"step {step}: loss {float(loss):.4f}")
    print(f"final loss: {float(loss):.4f}")


if __name__ == "__main__":
    main()
